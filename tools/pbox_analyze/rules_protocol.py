"""Typestate protocol engine: declarative state machines checked against
every call site, path by path, per object binding.

The interprocedural layer PR 10's single-function passes couldn't reach:
every expensive bug family left in CHANGES.md is a *protocol* violation —
``begin_pass`` without ``end_pass``, a donefile uploaded before its data
verified, an admission ticket leaked on an exception path, ``close()``
hard-killing a drain that ``stop()`` never started.  Each protocol is a
small checked-in spec (:mod:`protocols`): states, per-op transitions,
states an op *requires*, and optionally the states a binding must reach
by scope exit.

The engine runs an abstract interpretation over each function body:

  * **bindings** are (a) locals assigned from a tracked constructor
    (precise: they start in the protocol's initial state), (b) receivers
    matching the spec's ``receivers`` regex (``self.table``,
    ``server.gate`` — they start in ⊤, the set of all states, because
    the object predates the function), or (c) the function scope itself
    (``scope_ops`` specs: ordered-operation discipline like
    stage→manifest→upload→verify→donefile-LAST);
  * state sets flow through if/else joins, loops (two iterations, so a
    ``begin_pass`` looping back onto an unclosed pass is caught),
    try/except (handlers enter from any state reachable in the body) and
    with blocks; ``return``/``raise`` record scope exits — pending
    ``finally`` blocks are applied first, so a release in a finally
    counts on the exception path it actually covers;
  * **definite-only reporting**: an op is flagged only when it is illegal
    from *every* currently-possible state, and an exit leak only when
    *no* possible state is an accepted end state.  ⊤-started receivers
    therefore never produce speculative findings — they narrow as ops
    are observed and only definite misuse fires;
  * **interprocedural**: a tracked local passed to a resolved project
    function applies that callee's summary (its state transform,
    computed by running the engine on the callee per start state,
    memoized); an unresolvable hand-off escapes the binding instead of
    guessing.  Class-level obligations (``state_dict`` must reach
    ``flush()``) are verified over the call graph's transitive closure,
    property reads included.

Guarded ops (``AdmissionGate.admit``) additionally get a structural
check: a matching release exists but is not reachable through a
``finally`` covering the acquire — the exact ticket-leak shape PR 7's
review fixed by hand.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .callgraph import CallGraph
from .core import Context, cached_walk, dotted


@dataclass(frozen=True)
class ProtocolSpec:
    """One declarative typestate protocol (see protocols.py for the
    shipped set and ARCHITECTURE.md for the how-to)."""

    rule: str
    name: str
    description: str
    states: tuple
    initial: str
    transitions: dict                       # op -> {from_state: to_state}
    require_state: dict = field(default_factory=dict)  # op -> set(states)
    end_states: frozenset | None = None     # required at scope exit
    ctors: frozenset = frozenset()          # ctor base names -> local bindings
    ctor_bare_only: bool = False            # match `open(...)`, not `x.open(...)`
    receivers: str | None = None            # regex over dotted receiver text
    end_check_receivers: bool = False
    guarded: frozenset = frozenset()        # ops needing finally-safe release
    release_ops: frozenset = frozenset()
    scope_ops: bool = False                 # ops matched on any receiver
    trigger: str | None = None              # scope specs: activation op
    hints: dict = field(default_factory=dict)  # op -> extra guidance

    @property
    def ops(self) -> set:
        return set(self.transitions) | set(self.require_state)


@dataclass(frozen=True)
class ImplObligation:
    """Class-level obligation: every listed method (as *defined* on the
    class — inherited bodies are checked once, on the base) must
    transitively reach ``must_call`` through the call graph."""

    cls: str
    methods: tuple
    must_call: tuple
    why: str
    rule: str = "protocol-impl-requires"


# --------------------------------------------------------------------------- #
# structural helper shared with rules_resources: is this acquire-like call
# covered by a try/finally that performs the matching release?
# --------------------------------------------------------------------------- #
def _enclosing_stmt(sf, node, fn):
    cur = node
    while cur is not None and cur is not fn:
        parent = sf.parent(cur)
        if isinstance(cur, ast.stmt):
            return cur, parent
        cur = parent
    return None, None


def _contains_release(stmts, match_release) -> bool:
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Call) and match_release(n):
                return True
    return False


def release_guarded(sf, fn, call, match_release) -> bool:
    """True when the release matched by ``match_release`` is guaranteed
    on exceptional exits from ``call``: it sits in the finalbody of an
    enclosing try, of a try *following* the acquire at any enclosing
    statement level, or (``if x.acquire(...):`` guards) of a try inside
    the guarded branch."""
    # enclosing try/finally at any depth
    cur = call
    while cur is not None and cur is not fn:
        parent = sf.parent(cur)
        if isinstance(parent, ast.Try) and parent.finalbody and \
                _contains_release(parent.finalbody, match_release):
            return True
        cur = parent

    def guarded_try_in(stmts) -> bool:
        for s in stmts:
            for n in ast.walk(s):
                if isinstance(n, ast.Try) and n.finalbody and \
                        _contains_release(n.finalbody, match_release):
                    return True
        return False

    # climb the statement chain, scanning following siblings at each
    # level (the acquire may sit inside its own try/except, with the
    # guarded section's try/finally as a LATER sibling one level up)
    stmt, owner = _enclosing_stmt(sf, call, fn)
    while stmt is not None:
        # `if x.acquire(blocking=False):` — the guarded branch
        if isinstance(stmt, ast.If) and any(
                n is call for n in ast.walk(stmt.test)):
            if guarded_try_in(stmt.body):
                return True
        if owner is None:
            break
        blocks = [getattr(owner, f, None)
                  for f in ("body", "orelse", "finalbody")]
        blocks += [h.body for h in getattr(owner, "handlers", []) or []]
        for block in blocks:
            if isinstance(block, list) and stmt in block:
                if guarded_try_in(block[block.index(stmt) + 1:]):
                    return True
                break
        if owner is fn or isinstance(
                owner, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        stmt, owner = (owner, sf.parent(owner)) \
            if isinstance(owner, ast.stmt) else _enclosing_stmt(
                sf, owner, fn)
    return False


# --------------------------------------------------------------------------- #
# the abstract interpreter
# --------------------------------------------------------------------------- #
class _Interp:
    """One (function, protocol) analysis.  env maps binding keys to
    frozensets of possible states; copied at branch points."""

    def __init__(self, engine, spec: ProtocolSpec, fi, collect=True,
                 seed=None):
        self.engine = engine
        self.spec = spec
        self.fi = fi
        self.sf = fi.sf
        self.collect = collect
        self.seed = seed or {}
        self.findings: list = []
        self.exit_states: dict = {}     # bkey -> set of states at any exit
        self.alias: dict = {}           # local name -> binding key
        self.escaped: set = set()
        self.managed: set = set()
        self.callee_escaped: set = set()  # bkeys a callee summary escaped
        self._finals: list = []         # pending finalbody stack
        self._loops: list = []          # per-loop continue/break env lists
        self._seen: set = set()
        self._re = re.compile(spec.receivers) if spec.receivers else None

    # -- top level ---------------------------------------------------------- #
    def run(self) -> "_Interp":
        env = {k: frozenset(v) for k, v in self.seed.items()}
        if self.spec.scope_ops:
            env[("scope",)] = frozenset({self.spec.initial})
        env, live = self._block(self.fi.node.body, env)
        if live:
            self._exit(env, self.fi.node, "fall")
        return self

    # -- findings ----------------------------------------------------------- #
    def _emit(self, node, message) -> None:
        if not self.collect:
            return
        key = (getattr(node, "lineno", 0), message)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(
            self.sf.finding(self.spec.rule, node, message))

    @staticmethod
    def _bdesc(bkey) -> str:
        if bkey[0] == "scope":
            return "this function"
        return f"{bkey[1]!r}"

    # -- env helpers -------------------------------------------------------- #
    def _join(self, a: dict, b: dict) -> dict:
        """Union of states per binding.  A receiver binding present on
        one side only widens to ⊤ on the other — its first touch may
        itself have raised (AdmissionGate.admit acquires OR raises), so
        the op's post-state is not definite on the join.  A ctor-local
        present on one side only keeps its states: its creation is
        visible, and a leak on the creating branch is a real leak."""
        top = frozenset(self.spec.states)
        out = dict(a)
        for k, v in b.items():
            if k in out:
                out[k] = out[k] | v
            else:
                out[k] = v | top if k[0] == "recv" else v
        for k in out:
            if k not in b and k[0] == "recv":
                out[k] = out[k] | top
        return out

    def _exit(self, env, node, kind) -> None:
        spec = self.spec
        # pending finally blocks run before the scope actually exits —
        # apply their state effects (silently) so a release-in-finally
        # counts on this path
        if self._finals:
            env = dict(env)
            was = self.collect
            finals, self._finals = self._finals, []  # no re-entry loops
            self.collect = False
            try:
                for fb in reversed(finals):
                    env, _ = self._block(fb, env)
            finally:
                self.collect = was
                self._finals = finals
        for bkey, states in env.items():
            self.exit_states.setdefault(bkey, set()).update(states)
            if spec.end_states is None or not states:
                continue
            if bkey in self.escaped or bkey in self.managed:
                continue
            if bkey[0] == "scope":
                continue
            if bkey[0] == "recv" and not spec.end_check_receivers:
                continue
            if not (states & spec.end_states):
                where = {
                    "return": "this return path",
                    "raise": "this raise path",
                    "fall": "the end of the function",
                }[kind]
                self._emit(node, (
                    f"[{spec.name}] {self._bdesc(bkey)} is still in state "
                    f"{'/'.join(sorted(states))} at {where} — expected "
                    f"{'/'.join(sorted(spec.end_states))}"
                ))

    # -- op application ----------------------------------------------------- #
    def _apply_op(self, bkey, op, node, env) -> None:
        spec = self.spec
        states = env.get(bkey)
        if states is None:
            return
        req = spec.require_state.get(op)
        if req:
            hit = states & frozenset(req)
            if not hit:
                hint = spec.hints.get(op, "")
                self._emit(node, (
                    f"[{spec.name}] {op}() requires state "
                    f"{'/'.join(sorted(req))} but {self._bdesc(bkey)} is "
                    f"{'/'.join(sorted(states))}"
                    + (f" — {hint}" if hint else "")
                ))
            else:
                states = hit
        tmap = spec.transitions.get(op)
        if tmap:
            legal = states & frozenset(tmap)
            if not legal:
                hint = spec.hints.get(op, "")
                self._emit(node, (
                    f"[{spec.name}] {op}() in state "
                    f"{'/'.join(sorted(states))} — legal only from "
                    f"{'/'.join(sorted(tmap))}"
                    + (f" — {hint}" if hint else "")
                ))
                states = frozenset(tmap.values())
            else:
                states = frozenset(tmap[s] for s in legal)
        env[bkey] = states

    # -- call / expression scanning ----------------------------------------- #
    def _ctor_chain(self, expr):
        """(chained op names, innermost ctor call) when ``expr`` is
        ``Ctor(...)`` or ``Ctor(...).op().op()`` for a tracked ctor."""
        ops: list = []
        cur = expr
        while (
            isinstance(cur, ast.Call)
            and isinstance(cur.func, ast.Attribute)
        ):
            ops.append(cur.func.attr)
            cur = cur.func.value
        if not isinstance(cur, ast.Call):
            return None
        if self.spec.ctor_bare_only and not isinstance(cur.func, ast.Name):
            return None
        name = dotted(cur.func)
        base = name.rsplit(".", 1)[-1] if name else ""
        if base in self.spec.ctors:
            return list(reversed(ops)), cur
        return None

    def _bkey_for(self, recv, env, create=True):
        if isinstance(recv, ast.Name):
            if recv.id in self.alias:
                return self.alias[recv.id]
            if ("local", recv.id) in env:
                return ("local", recv.id)
        text = dotted(recv)
        if text and self._re and self._re.search(text):
            bkey = ("recv", text)
            if bkey not in env and create:
                env[bkey] = frozenset(self.spec.states)  # ⊤
            return bkey
        return None

    def _calls_in(self, expr):
        out: list = []
        stack = [expr]
        while stack:
            n = stack.pop()
            if n is None or isinstance(
                    n, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        out.sort(key=lambda c: (c.lineno, c.col_offset))
        return out

    def _scan_expr(self, expr, env) -> None:
        if expr is None:
            return
        for call in self._calls_in(expr):
            self._handle_call(call, env)

    def _handle_call(self, call, env) -> None:
        spec = self.spec
        func = call.func
        last = ""
        if isinstance(func, ast.Attribute):
            last = func.attr
        elif isinstance(func, ast.Name):
            last = func.id
        if spec.scope_ops:
            if last in spec.ops and ("scope",) in env:
                self._apply_op(("scope",), last, call, env)
            return
        if isinstance(func, ast.Attribute) and last in spec.ops:
            bkey = self._bkey_for(func.value, env)
            if bkey is not None and bkey in env:
                self._apply_op(bkey, last, call, env)
                if last in spec.guarded:
                    self._check_guarded(call, func.value, env)
        # tracked locals handed to other callables: summary or escape
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            if not isinstance(a, ast.Name):
                continue
            bkey = self.alias.get(a.id, ("local", a.id))
            if bkey in env and bkey[0] == "local" \
                    and bkey not in self.escaped:
                if not self._apply_summary(call, a, bkey, env):
                    self.escaped.add(bkey)

    def _apply_summary(self, call, name_node, bkey, env) -> bool:
        eng = self.engine
        if eng is None or eng.cg is None:
            return False
        fid = eng.resolve_call(self.fi, call)
        if fid is None:
            return False
        param = eng.param_for_arg(fid, call, name_node)
        if param is None:
            return False
        tf = eng.summary(fid, param, self.spec)
        if tf is None:
            return False
        out: set = set()
        for s in env[bkey]:
            out |= tf.get(s, {s})
        env[bkey] = frozenset(out)
        return True

    def _check_guarded(self, call, recv, env) -> None:
        spec = self.spec
        recv_text = dotted(recv)

        def match_release(n):
            return (
                isinstance(n.func, ast.Attribute)
                and n.func.attr in spec.release_ops
                and dotted(n.func.value) == recv_text
            )

        # no release anywhere: the end-state analysis reports it once
        if not any(
            match_release(n)
            for n in cached_walk(self.fi.node)
            if isinstance(n, ast.Call)
        ):
            return
        if not release_guarded(self.sf, self.fi.node, call, match_release):
            self._emit(call, (
                f"[{spec.name}] {call.func.attr}() on {recv_text!r} has a "
                f"matching {'/'.join(sorted(spec.release_ops))}() but not "
                "in a finally covering this acquire — an exception here "
                "leaks the ticket; wrap the guarded section in try/finally"
            ))

    def _escape_names(self, expr, env) -> None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name):
                bkey = self.alias.get(n.id, ("local", n.id))
                if bkey in env and bkey[0] == "local":
                    self.escaped.add(bkey)

    # -- statements ---------------------------------------------------------- #
    def _block(self, body, env):
        live = True
        for stmt in body:
            env, live = self._stmt(stmt, env)
            if not live:
                break
        return env, live

    def _scan_stmt_exprs(self, stmt, env) -> None:
        for _, value in ast.iter_fields(stmt):
            if isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.AST) and not isinstance(
                            v, (ast.stmt, ast.ExceptHandler)):
                        self._scan_expr(v, env)
            elif isinstance(value, ast.AST) and not isinstance(
                    value, (ast.stmt, ast.ExceptHandler)):
                self._scan_expr(value, env)

    def _stmt(self, stmt, env):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return env, True  # separate scope, analyzed on its own
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value, env)
                self._escape_names(stmt.value, env)
            self._exit(env, stmt, "return")
            return env, False
        if isinstance(stmt, ast.Raise):
            self._scan_stmt_exprs(stmt, env)
            self._exit(env, stmt, "raise")
            return env, False
        if isinstance(stmt, (ast.Break, ast.Continue)):
            if self._loops:
                which = "b" if isinstance(stmt, ast.Break) else "c"
                self._loops[-1][which].append(dict(env))
            return env, False
        if isinstance(stmt, ast.Assign):
            return self._assign(stmt, env)
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, env)
            e1, l1 = self._block(stmt.body, dict(env))
            e2, l2 = self._block(stmt.orelse, dict(env))
            if l1 and l2:
                return self._join(e1, e2), True
            if l1:
                return e1, True
            if l2:
                return e2, True
            return env, False
        if isinstance(stmt, (ast.While, ast.For)):
            self._scan_expr(getattr(stmt, "test", None)
                            or getattr(stmt, "iter", None), env)
            self._loops.append({"b": [], "c": []})
            e1, l1 = self._block(stmt.body, dict(env))
            frame = self._loops.pop()
            exits = list(frame["b"])
            # the state at a SECOND iteration is the state at the end of
            # the first (normal completion or `continue`) — begin_pass
            # looping back onto an unclosed pass is the cross-iteration
            # bug this second lap exists to catch
            back = ([e1] if l1 else []) + frame["c"]
            out = dict(env)  # zero-iteration path
            if back:
                eb = back[0]
                for x in back[1:]:
                    eb = self._join(eb, x)
                self._loops.append({"b": [], "c": []})
                e2, l2 = self._block(stmt.body, dict(eb))
                f2 = self._loops.pop()
                exits += f2["b"]
                out = self._join(out, eb)
                for x in ([e2] if l2 else []) + f2["c"]:
                    out = self._join(out, x)
            for x in exits:
                out = self._join(out, x)
            if stmt.orelse:
                return self._block(stmt.orelse, out)
            return out, True
        if isinstance(stmt, ast.Try):
            return self._try(stmt, env)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, env)
        self._scan_stmt_exprs(stmt, env)
        return env, True

    def _assign(self, stmt, env):
        self._scan_expr(stmt.value, env)
        targets = stmt.targets
        if len(targets) == 1 and isinstance(targets[0], ast.Name):
            name = targets[0].id
            chain = self._ctor_chain(stmt.value)
            if chain is not None:
                ops, _ctor = chain
                bkey = ("local", name)
                env[bkey] = frozenset({self.spec.initial})
                self.escaped.discard(bkey)
                self.managed.discard(bkey)
                self.alias.pop(name, None)
                for op in ops:
                    if op in self.spec.ops:
                        self._apply_op(bkey, op, stmt.value, env)
                return env, True
            if isinstance(stmt.value, ast.Name):
                src = self.alias.get(stmt.value.id,
                                     ("local", stmt.value.id))
                if src in env:
                    self.alias[name] = src
                    return env, True
            src_key = None
            if isinstance(stmt.value, ast.Attribute):
                text = dotted(stmt.value)
                if text and self._re and self._re.search(text):
                    src_key = ("recv", text)
            if src_key is not None:
                self.alias[name] = src_key
                if src_key not in env:
                    env[src_key] = frozenset(self.spec.states)
                return env, True
            env.pop(("local", name), None)
            self.alias.pop(name, None)
        else:
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)) and \
                        isinstance(stmt.value, ast.Name):
                    bkey = self.alias.get(
                        stmt.value.id, ("local", stmt.value.id))
                    if bkey in env and bkey[0] == "local":
                        self.escaped.add(bkey)
        return env, True

    def _try(self, stmt, env):
        self._finals.append(stmt.finalbody or [])
        try:
            any_env = dict(env)
            cur, live = dict(env), True
            for s in stmt.body:
                cur, live = self._stmt(s, cur)
                any_env = self._join(any_env, cur)
                if not live:
                    break
            if live and stmt.orelse:
                cur, live = self._block(stmt.orelse, cur)
            branches = [cur] if live else []
            for h in stmt.handlers:
                he, hl = self._block(h.body, dict(any_env))
                if hl:
                    branches.append(he)
        finally:
            self._finals.pop()
        if branches:
            merged = branches[0]
            for b in branches[1:]:
                merged = self._join(merged, b)
            out_live = True
        else:
            merged, out_live = any_env, False
        if stmt.finalbody:
            merged, fl = self._block(stmt.finalbody, dict(merged))
            out_live = out_live and fl
        return merged, out_live

    def _with(self, stmt, env):
        created: list = []
        for item in stmt.items:
            self._scan_expr(item.context_expr, env)
            chain = self._ctor_chain(item.context_expr)
            if chain is not None and isinstance(
                    item.optional_vars, ast.Name):
                bkey = ("local", item.optional_vars.id)
                env[bkey] = frozenset({self.spec.initial})
                self.managed.add(bkey)
                created.append(bkey)
            elif isinstance(item.context_expr, ast.Name):
                bkey = self.alias.get(
                    item.context_expr.id,
                    ("local", item.context_expr.id))
                if bkey in env and "close" in self.spec.transitions:
                    # `with f:` closes on exit, whatever the body does
                    created.append(bkey)
                    self.managed.add(bkey)
        env, live = self._block(stmt.body, env)
        for bkey in created:
            env.pop(bkey, None)
        return env, live


# --------------------------------------------------------------------------- #
# the engine: summaries, call resolution, per-function driving
# --------------------------------------------------------------------------- #
class Engine:
    def __init__(self, ctx: Context, specs):
        self.ctx = ctx
        self.cg = CallGraph.of(ctx)
        self.specs = list(specs)
        self._summaries: dict = {}
        self._in_progress: set = set()
        self._tokens: dict = {}  # fid -> called-name tokens

    # -- summaries ----------------------------------------------------------- #
    def resolve_call(self, fi, call):
        lt = self.cg._local_types(fi)
        return self.cg._resolve_call_target(fi, lt, call.func)

    def param_for_arg(self, fid, call, name_node):
        fi = self.cg.functions.get(fid)
        if fi is None:
            return None
        params = [a.arg for a in fi.node.args.args]
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        for i, a in enumerate(call.args):
            if a is name_node:
                return params[i] if i < len(params) else None
        for kw in call.keywords:
            if kw.value is name_node:
                return kw.arg
        return None

    def summary(self, fid, param, spec):
        """state -> set(states) transform of ``fid`` on its ``param``
        binding, or None when the callee escapes the binding (caller
        should escape too)."""
        key = (fid, param, spec.rule)
        if key in self._summaries:
            return self._summaries[key]
        if key in self._in_progress:
            return None  # recursion: give up, caller escapes
        fi = self.cg.functions.get(fid)
        if fi is None:
            return None
        self._in_progress.add(key)
        try:
            tf: dict = {}
            for s in spec.states:
                it = _Interp(self, spec, fi, collect=False,
                             seed={("local", param): {s}}).run()
                bkey = ("local", param)
                if bkey in it.escaped or bkey in it.callee_escaped:
                    tf = None
                    break
                out = it.exit_states.get(bkey, set())
                tf[s] = set(out) if out else {s}
        finally:
            self._in_progress.discard(key)
        self._summaries[key] = tf
        return tf

    # -- driving ------------------------------------------------------------- #
    def _fn_tokens(self, fi) -> set:
        toks = self._tokens.get(fi.id)
        if toks is None:
            toks = set()
            for n in cached_walk(fi.node):
                if isinstance(n, ast.Call):
                    if isinstance(n.func, ast.Attribute):
                        toks.add(n.func.attr)
                    elif isinstance(n.func, ast.Name):
                        toks.add(n.func.id)
            self._tokens[fi.id] = toks
        return toks

    def run(self) -> list:
        findings: list = []
        rel_files = {sf.rel for sf in self.ctx.files}
        for fi in self.cg.functions.values():
            if fi.sf.rel not in rel_files:
                continue
            toks = self._fn_tokens(fi)
            for spec in self.specs:
                gate = spec.ops | set(spec.ctors)
                if spec.trigger is not None and spec.trigger not in toks:
                    continue
                if not (gate & toks):
                    continue
                findings.extend(
                    _Interp(self, spec, fi).run().findings)
        return findings


def impl_findings(ctx: Context, obligations) -> list:
    cg = CallGraph.of(ctx)
    findings: list = []
    for ob in obligations:
        for cid, ci in cg.classes.items():
            if ci.name != ob.cls:
                continue
            for m in ob.methods:
                fid = ci.methods.get(m)  # own definitions only
                if fid is None:
                    continue
                reach = {fid} | cg.transitive_callees(fid)
                for target_name in ob.must_call:
                    target = cg.resolve_method(cid, target_name)
                    if target is not None and target in reach:
                        continue
                    # the obligation may name a module-level helper
                    # (write_manifest), not a method of the class
                    if target is None and any(
                        r in cg.functions
                        and cg.functions[r].name == target_name
                        for r in reach
                    ):
                        continue
                    fi = cg.functions[fid]
                    findings.append(fi.sf.finding(
                        ob.rule, fi.node,
                        f"[{ci.name}] {m}() must (transitively) call "
                        f"{target_name}() — {ob.why}",
                    ))
    return findings


# --------------------------------------------------------------------------- #
# pass registration
# --------------------------------------------------------------------------- #
def _load_specs():
    from . import protocols
    return protocols.PROTOCOLS, protocols.OBLIGATIONS


RULES = {
    "protocol-impl-requires": (
        "a method listed in a protocol obligation no longer reaches its "
        "required call (e.g. state_dict without the flush barrier)"
    ),
}


def _register_rules() -> None:
    specs, _ = _load_specs()
    for spec in specs:
        RULES[spec.rule] = spec.description


_register_rules()


def run(ctx: Context) -> list:
    specs, obligations = _load_specs()
    findings = Engine(ctx, specs).run()
    findings.extend(impl_findings(ctx, obligations))
    return findings
