"""pbox-lint CLI: ``python tools/pbox_analyze.py --all --json ...``.

Exit codes: 0 clean, 1 findings (incl. stale-baseline errors), 2 the
analyzer itself is misconfigured (bad baseline schema, unknown rule,
bad git ref).

Modes:

  --all                analyze the default roots (package, tools, bench)
  PATH [PATH ...]      analyze specific files/directories instead
  --changed [REF]      findings only on lines touched vs the git ref
                       (default HEAD) — the fast pre-commit entry point
  --rules a,b          run only the named rules
  --list-rules         print the rule catalog and exit
  --json               machine-readable output (list of finding dicts)
  --update-baseline    accept every current finding into the baseline
  --publish-root PATH  additionally audit a publish root (repeatable;
                       runtime data check, imports the package)
  --store-root PATH    additionally audit a durable-log store root
                       (repeatable; runtime data check)
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import re
import subprocess
import sys
import time

from . import all_rules, run_passes
from . import baseline as baseline_mod
from .core import REPO, Context, Finding, discover_files


def parse_changed_diff(text: str) -> dict:
    """{post-image repo-relative path: set of touched 1-based lines} from
    unified-diff text.

    Robust to the shapes a working tree actually produces: deleted files
    (``+++ /dev/null`` — their hunks belong to no current file and must
    not bleed onto the previous file), renames (``+++ b/<new path>`` is
    the analyzable file; a pure rename with no hunks touches nothing),
    and mode-only entries (no ``+++`` line at all)."""
    touched: dict = {}
    current = None
    for line in text.splitlines():
        if line.startswith("+++ b/"):
            current = line[6:]
            touched.setdefault(current, set())
        elif line.startswith("+++ "):
            current = None  # '+++ /dev/null': the file is gone
        elif line.startswith("diff --git"):
            current = None  # a headerless entry must not inherit state
        elif line.startswith("@@") and current is not None:
            m = re.search(r"\+(\d+)(?:,(\d+))?", line)
            if m:
                start = int(m.group(1))
                count = int(m.group(2)) if m.group(2) is not None else 1
                touched[current].update(range(start, start + max(count, 1)))
    return touched


def _changed_lines(ref: str) -> dict:
    """{repo-relative path: set of touched 1-based lines} vs the ref."""
    try:
        out = subprocess.run(
            ["git", "diff", "--unified=0", "--find-renames", ref,
             "--", "*.py"],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        raise SystemExit(f"ERROR: git diff {ref} failed: {e}")
    if out.returncode != 0:
        raise SystemExit(
            f"ERROR: git diff {ref} failed: {out.stderr.strip()}")
    return parse_changed_diff(out.stdout)


def _resolve_paths(paths: list) -> list:
    out: list = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(REPO, p)
        if not os.path.exists(full):
            raise SystemExit(f"ERROR: no such path: {p}")
        out.extend(discover_files(REPO, [full]) if os.path.isdir(full)
                   else [full])
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/pbox_analyze.py",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: --all roots)")
    ap.add_argument("--all", action="store_true",
                    help="analyze the default roots (paddlebox_tpu/, "
                         "tools/, bench.py)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ap.add_argument("--rules", metavar="A,B",
                    help="comma-separated rule ids to run (default all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--changed", nargs="?", const="HEAD", metavar="REF",
                    help="report only findings on lines touched vs REF "
                         "(default HEAD) — the pre-commit fast path")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write every current finding into the baseline "
                         "(new entries get a placeholder reason to edit)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report raw findings, ignoring the baseline")
    ap.add_argument("--publish-root", action="append", default=[],
                    metavar="PATH",
                    help="also audit a publish root (runtime data check)")
    ap.add_argument("--store-root", action="append", default=[],
                    metavar="PATH",
                    help="also audit a durable-log store root "
                         "(runtime data check)")
    args = ap.parse_args(argv)

    rules_catalog = all_rules()
    if args.list_rules:
        width = max(len(r) for r in rules_catalog)
        for rule in sorted(rules_catalog):
            print(f"{rule:<{width}}  {rules_catalog[rule]}")
        return 0

    rules = None
    if args.rules:
        # globs select rule families: --rules 'spmd-*' runs the four
        # SPMD passes, --rules 'protocol-*' the typestate specs
        requested = {r.strip() for r in args.rules.split(",") if r.strip()}
        rules = set()
        unknown = set()
        for pat in requested:
            if any(ch in pat for ch in "*?["):
                hits = {r for r in rules_catalog
                        if fnmatch.fnmatchcase(r, pat)}
                if hits:
                    rules |= hits
                else:
                    unknown.add(pat)
            elif pat in rules_catalog:
                rules.add(pat)
            else:
                unknown.add(pat)
        if unknown:
            print(f"ERROR: unknown rule(s): {', '.join(sorted(unknown))} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2

    t0 = time.monotonic()
    ctx = Context(_resolve_paths(args.paths) if args.paths else None)
    findings = ctx.parse_errors() + run_passes(ctx, rules)

    # inline suppressions
    kept: list = []
    suppressed = 0
    for f in findings:
        sf = ctx.by_rel.get(f.file)
        if sf is not None and sf.suppressed(f):
            suppressed += 1
        else:
            kept.append(f)

    # publish roots (opt-in runtime audit)
    for root in args.publish_root:
        from .publish import check_publish_root
        errors, warnings = check_publish_root(root)
        for w in warnings:
            print(f"WARNING: {root}: {w}", file=sys.stderr)
        kept += [
            Finding(file=root, line=1, rule="publish-dir", message=e)
            for e in errors
        ]

    # store roots (opt-in runtime audit of the durable cold tier)
    for root in args.store_root:
        from .publish import check_store_root
        errors, warnings = check_store_root(root)
        for w in warnings:
            print(f"WARNING: {root}: {w}", file=sys.stderr)
        kept += [
            Finding(file=root, line=1, rule="store-dir", message=e)
            for e in errors
        ]

    # baseline
    baselined: list = []
    if args.update_baseline:
        entries = baseline_mod.update(kept)
        print(f"baseline updated: {len(entries)} entr(y/ies) written to "
              f"{os.path.relpath(baseline_mod.BASELINE_PATH, REPO)}")
        return 0
    if not args.no_baseline:
        try:
            entries = baseline_mod.load()
        except baseline_mod.BaselineError as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return 2
        kept, baselined, stale = baseline_mod.apply(kept, entries)
        kept += stale

    # incremental mode: only touched lines.  stale-baseline findings
    # survive the filter (a stale entry is a whole-repo invariant), and
    # so does a parse error in any touched file — a mid-edit syntax
    # error reported at line 1 would otherwise vanish whenever line 1
    # itself wasn't part of the diff.
    if args.changed is not None:
        touched = _changed_lines(args.changed)
        kept = [
            f for f in kept
            if f.rule == "stale-baseline"
            or (f.rule == "parse-error" and f.file in touched)
            or f.line in touched.get(f.file, ())
        ]

    kept.sort()
    elapsed = time.monotonic() - t0
    if args.json:
        print(json.dumps([f.to_dict() for f in kept], indent=2))
    else:
        for f in kept:
            print(f)
        scope = f"{len(ctx.files)} file(s)"
        if args.changed is not None:
            scope += f", changed vs {args.changed}"
        print(
            f"pbox-lint: {len(kept)} finding(s) ({suppressed} suppressed "
            f"inline, {len(baselined)} baselined) over {scope} "
            f"in {elapsed:.2f}s",
            file=sys.stderr,
        )
    return 1 if kept else 0
