#!/usr/bin/env python
"""Span-name drift check: every span recorded in code must be in the
ARCHITECTURE.md span catalog, and every cataloged span must still exist.

The span namespace is the postmortem contract the same way metric names
are the scrape contract: ``tools/pbox_doctor.py`` timelines, Perfetto
traces and flight-recorder dumps are read by operators who grep the
ARCHITECTURE.md catalog for what a name means — an undocumented span is
unexplainable evidence, and a documented-but-removed span sends the
reader hunting for records that no longer exist.  Cross-checked in both
directions, exactly like the metric-name, fault-site and env-flag
guards:

  * **recorded** — literal first arguments of ``span(`` /
    ``telemetry.span(`` / ``add_span(`` / ``instant(`` /
    ``telemetry.instant(`` calls in the package + bench.py; f-string
    placeholders (``f"sync.apply.{kind}"``) normalize to ``*`` so a
    dynamic family stays one catalog row;
  * **cataloged** — backticked names in the first column of the span
    catalog table under ARCHITECTURE.md's "## Distributed tracing"
    section (``<x>`` placeholders also normalize to ``*``).

Usage:
    python tools/check_span_names.py            # check, exit 1 on drift
    python tools/check_span_names.py --list     # dump what was found
"""

from __future__ import annotations

import argparse
import fnmatch
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARCH = os.path.join(REPO, "ARCHITECTURE.md")

# span-recording call with a (possibly f-) string literal first argument.
# Matches bare span(/instant( and their telemetry./tracer-method forms;
# definition sites (def span(...) take no string literal and don't match.
_CALL_RE = re.compile(
    r"""\b(?:span|add_span|instant)\(\s*
        (f?)(["'])([^"']+)\2""",
    re.VERBOSE | re.DOTALL,
)
_TABLE_ROW_RE = re.compile(r"^\|\s*`([^`]+)`")


def scan_sources() -> dict:
    """{normalized span name: first 'file:line' seen}."""
    roots = [os.path.join(REPO, "paddlebox_tpu"),
             os.path.join(REPO, "bench.py")]
    found: dict = {}
    for root in roots:
        files = [root] if root.endswith(".py") else [
            os.path.join(d, f)
            for d, _, fs in os.walk(root)
            for f in fs
            if f.endswith(".py")
        ]
        for path in sorted(files):
            with open(path) as fh:
                text = fh.read()
            for m in _CALL_RE.finditer(text):
                is_f, name = m.group(1), m.group(3)
                if is_f:
                    name = re.sub(r"\{[^}]*\}", "*", name)
                # skip docstring/prose fragments that happen to match
                # ("span(" examples) — a real span name is dotted-or-bare
                # lowercase identifier text
                if not re.fullmatch(r"[a-z0-9_.*]+", name):
                    continue
                if name == "name":
                    continue  # the docs' ``span("name")`` placeholder
                line = text.count("\n", 0, m.start()) + 1
                rel = os.path.relpath(path, REPO)
                found.setdefault(name, f"{rel}:{line}")
    return found


def catalog_patterns() -> dict:
    """{glob pattern: 'ARCHITECTURE.md:line'} from the span catalog table
    in the '## Distributed tracing' section."""
    pats: dict = {}
    in_sec = False
    with open(ARCH) as fh:
        for i, line in enumerate(fh, 1):
            if line.startswith("## "):
                in_sec = line.strip().lower().startswith(
                    "## distributed tracing")
                continue
            if not in_sec:
                continue
            m = _TABLE_ROW_RE.match(line.strip())
            if m:
                pats[re.sub(r"<[^>]*>", "*", m.group(1))] = \
                    f"ARCHITECTURE.md:{i}"
    return pats


def check() -> tuple:
    found = scan_sources()
    pats = catalog_patterns()
    missing = []
    for name, where in sorted(found.items()):
        concrete = name.replace("*", "ANY")
        if not any(fnmatch.fnmatchcase(concrete, p) for p in pats):
            missing.append((name, where))
    stale = []
    for pat, where in sorted(pats.items()):
        if not any(
            fnmatch.fnmatchcase(name.replace("*", "ANY"), pat)
            for name in found
        ):
            stale.append((pat, where))
    return missing, stale, found, pats


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--list", action="store_true",
                    help="print every discovered span name and exit 0")
    args = ap.parse_args(argv)
    missing, stale, found, pats = check()
    if args.list:
        for name, where in sorted(found.items()):
            mark = "!" if any(name == m[0] for m in missing) else " "
            print(f"{mark} {name:40s} {where}")
        return 0
    if not pats:
        print("ERROR: no span catalog table found in ARCHITECTURE.md "
              "('## Distributed tracing' section)", file=sys.stderr)
        return 2
    rc = 0
    if missing:
        print("span names missing from the ARCHITECTURE.md span catalog "
              "(## Distributed tracing & postmortems):", file=sys.stderr)
        for name, where in missing:
            print(f"  {name}  ({where})", file=sys.stderr)
        rc = 1
    if stale:
        print("span catalog rows matching no recorded span "
              "(stale docs):", file=sys.stderr)
        for pat, where in stale:
            print(f"  {pat}  ({where})", file=sys.stderr)
        rc = 1
    if rc:
        print(f"{len(missing)} missing + {len(stale)} stale; fix the "
              "catalog or the code.", file=sys.stderr)
    else:
        print(f"span catalog OK: {len(found)} recorded span name(s) "
              f"covered by {len(pats)} catalog row(s)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
