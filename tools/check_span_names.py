#!/usr/bin/env python
"""Span-name drift check: every span recorded in code must be in the
ARCHITECTURE.md span catalog, and every cataloged span must still exist.

Thin wrapper: the implementation moved into the pbox-lint framework
(tools/pbox_analyze/rules_drift.py, rule ``span-name-drift``).  This CLI
and its module-level functions are preserved for tier-1 tests and docs.

Usage:
    python tools/check_span_names.py            # check, exit 1 on drift
    python tools/check_span_names.py --list     # dump what was found
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pbox_analyze import rules_drift  # noqa: E402


def scan_sources() -> dict:
    """{normalized span name: first 'file:line' seen}."""
    return rules_drift.span_scan_sources()


def catalog_patterns() -> dict:
    """{glob pattern: 'ARCHITECTURE.md:line'} from the span catalog table
    in the '## Distributed tracing' section."""
    return rules_drift.span_catalog_patterns()


def check() -> tuple:
    """(missing, stale, found, pats): both drift directions plus the raw
    scan results."""
    return rules_drift.span_check()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--list", action="store_true",
                    help="print every discovered span name and exit 0")
    args = ap.parse_args(argv)
    missing, stale, found, pats = check()
    if args.list:
        for name, where in sorted(found.items()):
            mark = "!" if any(name == m[0] for m in missing) else " "
            print(f"{mark} {name:40s} {where}")
        return 0
    if not pats:
        print("ERROR: no span catalog table found in ARCHITECTURE.md "
              "('## Distributed tracing' section)", file=sys.stderr)
        return 2
    rc = 0
    if missing:
        print("span names missing from the ARCHITECTURE.md span catalog "
              "(## Distributed tracing & postmortems):", file=sys.stderr)
        for name, where in missing:
            print(f"  {name}  ({where})", file=sys.stderr)
        rc = 1
    if stale:
        print("span catalog rows matching no recorded span "
              "(stale docs):", file=sys.stderr)
        for pat, where in stale:
            print(f"  {pat}  ({where})", file=sys.stderr)
        rc = 1
    if rc:
        print(f"{len(missing)} missing + {len(stale)} stale; fix the "
              "catalog or the code.", file=sys.stderr)
    else:
        print(f"span catalog OK: {len(found)} recorded span name(s) "
              f"covered by {len(pats)} catalog row(s)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
