#!/usr/bin/env python
"""Store-root lint: crash-debris vs damage audit for the durable cold tier.

Thin wrapper over ``pbox_analyze.publish.check_store_root`` (rule
``store-dir`` — opt-in via ``tools/pbox_analyze.py --store-root``, since
it audits runtime data rather than source).  The line it draws is the
store's own crash contract (ARCHITECTURE.md "Durable cold tier"):
damage to the CURRENT-committed generation is an error; orphan
segments/manifests and torn tails are warnings — legal crash debris,
named so an operator can garbage-collect with confidence.

Usage:
    python tools/check_store_dir.py ROOT [--strict] [--quiet]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pbox_analyze.publish import check_store_root  # noqa: E402,F401


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", help="durable-log store root to lint")
    ap.add_argument("--strict", action="store_true",
                    help="treat warnings as errors")
    ap.add_argument("--quiet", action="store_true",
                    help="print nothing on success")
    args = ap.parse_args(argv)
    errors, warnings = check_store_root(args.root)
    for w in warnings:
        print(f"WARNING: {w}", file=sys.stderr)
    for e in errors:
        print(f"ERROR: {e}", file=sys.stderr)
    if errors or (args.strict and warnings):
        print(f"{args.root}: {len(errors)} error(s), "
              f"{len(warnings)} warning(s)", file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"{args.root}: store root OK "
              f"({len(warnings)} warning(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
