#!/usr/bin/env python
"""pbox-lint entry point: ``python tools/pbox_analyze.py --all``.

The implementation lives in the ``pbox_analyze`` package next to this
file (the import system prefers the package over this same-named
script); this shim only exists so the CLI path stays a single obvious
file under tools/, like the check_* guards before it.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pbox_analyze.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
